"""Deterministic fault injection: the chaos half of dstpu-resilience.

The reference DeepSpeed treats failure as a first-class input (its
``elasticity/`` layer restarts shrunk worlds; Nebula-tier checkpoints
survive torn writes) but *proves* none of it — recovery is exercised only
when production breaks. This module makes failure reproducible: a
:class:`FaultPlan` is a seedable, JSON-serializable list of
:class:`FaultEvent` s, each firing at a named **host-side seam**:

======================  =====================================================
site                    where the engine calls :func:`fault_point`
======================  =====================================================
``step_begin``          just after the step span opens (host, untraced)
``step_end``            after ``_post_step`` bookkeeping (host, untraced)
``ckpt_io``             before a checkpoint data/meta file write attempt
``ckpt_tmp``            after the temp file is written, before ``os.replace``
======================  =====================================================

Event kinds:

- ``crash``    — SIGKILL this process at ``step_end`` of step ``step``
  (the preemption / hardware-loss case the elastic agent recovers from).
- ``stall``    — sleep ``delay_s`` inside the open step at ``step_begin``
  (drives the telemetry watchdog and its escalation path).
- ``io_error`` — raise ``OSError`` from the checkpoint write seam for the
  first ``count`` attempts on files matching ``match`` (exercises the
  store's retry-with-backoff).
- ``torn_write`` — truncate the temp file to half its bytes and die before
  the ``os.replace`` commit (a kill mid-save; the atomic-rename protocol
  must leave ``latest`` on the previous tag).
- ``grad_bitflip`` — XOR bit ``bit`` of element ``index`` of param leaf
  ``leaf`` at the ``numerics`` seam (host-side, before the step's
  dispatch): the silent-data-corruption case — a flipped exponent bit in
  HBM weights — that the guardian's sentinels must catch (the gradients
  computed from the corrupted weights spike or go non-finite).
- ``loss_spike`` — multiply param leaf ``leaf`` by ``factor`` at the same
  seam: a finite but violent divergence (the loss blows up without any
  non-finite value), exercising the gnorm/loss spike sentinel rather
  than the overflow bit.

The ``numerics`` seam passes a *mutator* callback (the engine's
``_inject_numerics_fault``) instead of a path — the plan stays host-side
and engine-agnostic; only the engine knows how to flip a bit in a sharded
device array. Both kinds are attempt-scoped like ``crash``: a corruption
injected into attempt 0 does not re-fire after the guardian's rollback
restarts the world, which is what lets the chaos harness assert the
rolled-back trajectory matches an uninterrupted run.

Zero overhead when off — the same contract as telemetry: with no plan
installed, :func:`fault_point` is one global ``None`` check, and nothing
is ever hooked into traced code (every seam above runs on the host
between dispatches). Events are scoped to a restart ``attempt`` (default
0) so a crash injected into attempt 0 does not re-fire after the elastic
agent restarts the world — the resumed run reads its attempt number from
the agent's ``DSTPU_ELASTIC`` env.

Install programmatically (:func:`install_plan`) or by env —
``DSTPU_FAULT_PLAN`` holds either inline JSON or ``@/path/to/plan.json``;
engine construction calls :func:`maybe_install_from_env`.
"""

from __future__ import annotations

import fnmatch
import json
import os
import signal
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

from ..utils.logging import logger

#: exit code the watchdog escalation path dies with — distinct from user
#: script failures so the elastic agent's logs attribute the restart.
STALL_EXIT_CODE = 97

#: exit code of an injected crash when SIGKILL is unavailable.
CRASH_EXIT_CODE = 137

#: exit code of a guardian-initiated rollback (resilience/guardian.py):
#: distinct from stalls and crashes so the elastic agent's logs attribute
#: the restart to a NUMERICS event, not a process failure.
GUARDIAN_EXIT_CODE = 96

_SITES = ("step_begin", "step_end", "ckpt_io", "ckpt_tmp", "numerics")
_KINDS = ("crash", "stall", "io_error", "torn_write",
          "grad_bitflip", "loss_spike")


@dataclass
class FaultEvent:
    """One scheduled failure. ``step`` scopes step-site events; ``match``
    (an fnmatch glob over the target file's basename) scopes IO-site
    events; ``rank`` of ``None`` means every process; ``attempt`` is the
    elastic restart generation the event belongs to; ``skip`` lets the
    first N matching occurrences pass unharmed (e.g. tear the THIRD save
    of a file), then the event fires ``count`` times."""

    kind: str
    step: Optional[int] = None
    match: str = "*"
    rank: Optional[int] = None
    attempt: int = 0
    count: int = 1
    skip: int = 0
    delay_s: float = 0.0
    exit_code: int = CRASH_EXIT_CODE
    # numerics-kind knobs (grad_bitflip / loss_spike): which param leaf —
    # ``leaf_match`` is an fnmatch glob over the flattened path key
    # (e.g. ``wte*`` targets the embedding, whose corruption reaches the
    # logits un-normalized; a flip inside a pre-LN block is silently
    # absorbed by the next LayerNorm — the textbook silent corruption),
    # else ``leaf`` indexes flatten order (-1 = largest leaf, or the
    # whole tree for loss_spike); which flat element; which bit (30 =
    # fp32 high exponent bit — small weights become huge); multiplier
    leaf: int = 0
    leaf_match: str = ""
    index: int = 0
    bit: int = 30
    factor: float = 1024.0
    fired: int = field(default=0, compare=False)
    seen: int = field(default=0, compare=False)

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {_KINDS})")

    @property
    def site(self) -> str:
        return {"crash": "step_end", "stall": "step_begin",
                "io_error": "ckpt_io", "torn_write": "ckpt_tmp",
                "grad_bitflip": "numerics",
                "loss_spike": "numerics"}[self.kind]


class FaultPlan:
    """An ordered, deterministic set of fault events plus the bookkeeping
    to fire each at most ``count`` times. Same plan + same workload →
    same failures, which is what lets a chaos run assert resume parity
    against an uninterrupted run."""

    def __init__(self, events: List[FaultEvent], seed: Optional[int] = None):
        self.events = list(events)
        self.seed = seed

    # -- construction ----------------------------------------------------
    @classmethod
    def sample(cls, seed: int, max_step: int,
               kinds: tuple = ("crash",), rank: Optional[int] = 0) -> "FaultPlan":
        """A seedable random plan: one event per kind at a step drawn
        uniformly from [1, max_step]. Deterministic in ``seed``."""
        import random
        rng = random.Random(seed)
        events = []
        for kind in kinds:
            step = rng.randint(1, max_step)
            if kind in ("io_error", "torn_write"):
                events.append(FaultEvent(kind=kind, rank=rank))
            else:
                events.append(FaultEvent(kind=kind, step=step, rank=rank,
                                         delay_s=1.0 if kind == "stall" else 0.0))
        return cls(events, seed=seed)

    _RUNTIME_FIELDS = ("fired", "seen")

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        doc = json.loads(text)
        if isinstance(doc, list):  # bare event list
            doc = {"events": doc}
        events = [FaultEvent(**{k: v for k, v in e.items()
                                if k not in cls._RUNTIME_FIELDS})
                  for e in doc.get("events", [])]
        return cls(events, seed=doc.get("seed"))

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "events": [{k: v for k, v in asdict(e).items()
                        if k not in self._RUNTIME_FIELDS}
                       for e in self.events],
        }, indent=2)

    # -- firing ----------------------------------------------------------
    def fire(self, site: str, step: Optional[int] = None,
             path: Optional[str] = None, tmp: Optional[str] = None,
             payload=None) -> None:
        attempt, rank = _current_attempt_rank()
        for e in self.events:
            if e.site != site or e.attempt != attempt or \
                    (e.rank is not None and e.rank != rank):
                continue
            if e.step is not None and step != e.step:
                continue
            if site in ("ckpt_io", "ckpt_tmp") and path is not None and \
                    not fnmatch.fnmatch(os.path.basename(path), e.match):
                continue
            # occurrence accounting: let the first `skip` matches pass,
            # then fire `count` times
            e.seen += 1
            if e.seen <= e.skip or e.fired >= e.count:
                continue
            e.fired += 1
            self._execute(e, site, step=step, path=path, tmp=tmp,
                          payload=payload)

    def _execute(self, e: FaultEvent, site: str, step, path, tmp,
                 payload=None) -> None:
        where = f"site={site} step={step} path={path}"
        if e.kind == "crash":
            logger.error(f"fault-injection: CRASH ({where})")
            _die(e.exit_code)
        elif e.kind == "stall":
            logger.warning(
                f"fault-injection: STALL {e.delay_s}s ({where})")
            time.sleep(e.delay_s)
        elif e.kind == "io_error":
            logger.warning(f"fault-injection: IO ERROR ({where})")
            raise OSError(f"injected IO error ({where})")
        elif e.kind == "torn_write":
            logger.error(f"fault-injection: TORN WRITE ({where})")
            if tmp is not None and os.path.exists(tmp):
                size = os.path.getsize(tmp)
                with open(tmp, "r+b") as f:
                    f.truncate(max(1, size // 2))
            _die(e.exit_code)
        elif e.kind in ("grad_bitflip", "loss_spike"):
            logger.error(f"fault-injection: {e.kind.upper()} "
                         f"leaf={e.leaf} ({where})")
            if payload is None:
                logger.warning(
                    f"numerics fault {e.kind} fired at a seam without a "
                    "mutator payload — nothing corrupted")
            else:
                payload(e)


def _die(exit_code: int) -> None:
    """Die the way a preempted worker dies: SIGKILL — no atexit hooks, no
    finally blocks, no flush. Falls back to ``os._exit`` where SIGKILL
    does not exist (windows CI)."""
    if hasattr(signal, "SIGKILL"):
        os.kill(os.getpid(), signal.SIGKILL)
    os._exit(exit_code)  # pragma: no cover - non-posix fallback


def parse_elastic_env() -> Dict[str, Any]:
    """The DSTPU_ELASTIC payload (world_size / batch config /
    restart_count / checkpoint_dir) as a dict — {} when absent or
    malformed. The ONE parser for the agent's env contract; initialize()
    and the chaos bookkeeping share it."""
    el = os.environ.get("DSTPU_ELASTIC")
    if not el:
        return {}
    try:
        doc = json.loads(el)
    except ValueError:
        return {}
    return doc if isinstance(doc, dict) else {}


def _current_attempt_rank() -> tuple:
    """(elastic restart attempt, process rank) — both host-side env reads,
    resolved lazily so the plan works with or without a live jax backend.
    Malformed env scopes to attempt 0."""
    try:
        attempt = int(parse_elastic_env().get("restart_count", 0) or 0)
    except (ValueError, TypeError):
        attempt = 0
    rank = int(os.environ.get("JAX_PROCESS_ID", "0") or 0)
    return attempt, rank


# -- process-global install ----------------------------------------------
_PLAN: Optional[FaultPlan] = None
_ENV_CHECKED = False


def install_plan(plan: Optional[FaultPlan]) -> None:
    global _PLAN
    _PLAN = plan
    if plan is not None:
        logger.warning(
            f"fault-injection plan installed ({len(plan.events)} events) — "
            "this process WILL fail on schedule")


def clear_plan() -> None:
    global _PLAN, _ENV_CHECKED
    _PLAN = None
    _ENV_CHECKED = False


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


def maybe_install_from_env() -> None:
    """Engine front door: ``DSTPU_FAULT_PLAN`` = inline JSON or
    ``@/path.json`` installs a plan once per process; absent → no-op."""
    global _ENV_CHECKED
    if _ENV_CHECKED or _PLAN is not None:
        return
    _ENV_CHECKED = True
    raw = os.environ.get("DSTPU_FAULT_PLAN", "").strip()
    if not raw:
        return
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            raw = f.read()
    install_plan(FaultPlan.from_json(raw))


def fault_point(site: str, step: Optional[int] = None,
                path: Optional[str] = None, tmp: Optional[str] = None,
                payload=None) -> None:
    """The seam call. One ``None`` check when no plan is installed —
    host-side code only; never reachable from traced functions.
    ``payload`` is the numerics-seam mutator callback (engine-provided);
    every other seam ignores it."""
    if _PLAN is not None:
        _PLAN.fire(site, step=step, path=path, tmp=tmp, payload=payload)


def fault_descriptor() -> Dict[str, Any]:
    """Telemetry/debug summary of the installed plan (event kinds and
    fire counts) — shows up in chaos_run reports."""
    if _PLAN is None:
        return {"installed": False}
    return {"installed": True, "seed": _PLAN.seed,
            "events": [asdict(e) for e in _PLAN.events]}
